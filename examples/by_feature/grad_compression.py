"""By-feature example: compressed cross-slice gradients (DDP comm hooks).

Analog of the reference feature example
(/root/reference/examples/by_feature/ddp_comm_hook.py): the same training
loop as the canonical NLP example, with the cross-replica gradient
all-reduce compressed. Where torch registers a DDP communication hook, here
one ShardingConfig line selects the hook family:

- ``grad_compression_dtype="bf16"|"fp16"|"int8"``  (dtype hooks)
- ``grad_compression_rank=R``                      (powerSGD hook)

The compressed hop only exists on a ``replica > 1`` mesh (the DCN axis of a
multi-slice deployment). This example builds replica=2 out of the local
devices so the CPU simulator / a single host demonstrates the mechanics.
"""

from __future__ import annotations

import argparse

import jax
import numpy as np
import optax

from accelerate_tpu import Accelerator, Model, ShardingConfig
from accelerate_tpu.models import EncoderClassifier, EncoderConfig
from accelerate_tpu.utils.random import set_seed

import os
import sys

sys.path.append(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from nlp_example import get_dataloaders  # noqa: E402


def training_function(config, args):
    # New Code #
    if args.powersgd_rank:
        sharding = ShardingConfig(
            replica=2, data_parallel=-1, grad_compression_rank=args.powersgd_rank
        )
    else:
        sharding = ShardingConfig(
            replica=2, data_parallel=-1, grad_compression_dtype=args.compression
        )
    accelerator = Accelerator(
        mixed_precision=args.mixed_precision, sharding_config=sharding
    )
    lr, num_epochs, seed = config["lr"], int(config["num_epochs"]), int(config["seed"])
    set_seed(seed)
    model_config = EncoderConfig.tiny() if (args.cpu or args.tiny) else EncoderConfig.bert_base()
    batch_size = int(config["batch_size"])

    train_dataloader, eval_dataloader = get_dataloaders(
        accelerator, batch_size, model_config,
        train_len=config.get("train_len", 128), eval_len=config.get("eval_len", 64),
    )
    model_def = EncoderClassifier(model_config, mesh=accelerator.mesh)
    variables = model_def.init_variables(
        jax.random.PRNGKey(seed), batch_size=batch_size,
        seq_len=min(model_config.max_seq_len, 128),
    )
    model, optimizer, train_dl, eval_dl = accelerator.prepare(
        Model(model_def, variables), optax.adamw(lr), train_dataloader, eval_dataloader
    )

    # New Code #
    # The compressed hop lives inside the FUSED step (it is a shard_map
    # program); build_train_step is therefore the path that compresses.
    def loss_fn(apply_fn, params, batch):
        return apply_fn(
            params, batch["input_ids"], attention_mask=batch["attention_mask"],
            token_type_ids=batch["token_type_ids"], labels=batch["labels"],
            deterministic=False,
        )["loss"]

    step = accelerator.build_train_step(loss_fn=loss_fn)

    for epoch in range(num_epochs):
        model.train()
        last = None
        for batch in train_dl:
            last = step(batch)
        accelerator.print(
            f"epoch {epoch}: loss {float(jax.device_get(last['loss'])):.4f} "
            f"grad_norm {float(jax.device_get(last['grad_norm'])):.4f}"
        )

        model.eval()
        correct = total = 0
        for batch in eval_dl:
            outputs = model(
                batch["input_ids"], attention_mask=batch["attention_mask"],
                token_type_ids=batch["token_type_ids"],
            )
            predictions = outputs["logits"].argmax(axis=-1)
            predictions, references = accelerator.gather_for_metrics(
                (predictions, batch["labels"])
            )
            correct += int((np.asarray(predictions) == np.asarray(references)).sum())
            total += int(np.asarray(references).shape[0])
        accelerator.print(f"epoch {epoch}: {{'accuracy': {correct / max(total, 1):.4f}}}")

    accelerator.end_training()


def main():
    parser = argparse.ArgumentParser(description="Training with compressed cross-replica gradients.")
    parser.add_argument("--mixed_precision", type=str, default=None,
                        choices=["no", "fp16", "bf16"])
    parser.add_argument("--compression", type=str, default="bf16",
                        choices=["bf16", "fp16", "int8"],
                        help="dtype of the cross-replica gradient hop")
    parser.add_argument("--powersgd_rank", type=int, default=None,
                        help="use the PowerSGD low-rank hook at this rank instead")
    parser.add_argument("--cpu", action="store_true", help="Run the tiny config on CPU.")
    parser.add_argument("--tiny", action="store_true", help="Tiny model/dataset (CI).")
    parser.add_argument("--num_epochs", type=int, default=None)
    args = parser.parse_args()
    if args.cpu:
        # env JAX_PLATFORMS=cpu is not enough on hosts whose sitecustomize
        # force-registers a TPU platform; set it before backend init
        jax.config.update("jax_platforms", "cpu")
    config = {"lr": 2e-5, "num_epochs": args.num_epochs or 2, "seed": 42, "batch_size": 16}
    if args.tiny or args.cpu:
        config.update({"train_len": 128, "eval_len": 64})
    training_function(config, args)


if __name__ == "__main__":
    main()
