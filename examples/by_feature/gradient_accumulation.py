"""By-feature example: automatic gradient accumulation.

Mirrors the reference feature example
(/root/reference/examples/by_feature/gradient_accumulation.py:160-185):
`Accelerator(gradient_accumulation_steps=N)` plus the
`with accelerator.accumulate(model):` context, which gates the optimizer
step and the gradient synchronization automatically — the manual
`if step % accumulation == 0` bookkeeping from nlp_example.py disappears.

On TPU the accumulation loop is jit-fused: micro-batch gradients sum on
device in fp32 and the implicit data-parallel psum fires once per effective
batch, so N accumulated micro-steps cost the same HBM traffic as one big
step. bf16 is the recommended precision (--mixed_precision bf16).
"""

from __future__ import annotations

import argparse

import jax
import numpy as np
import optax

from accelerate_tpu import Accelerator, DataLoader, Model
from accelerate_tpu.models import EncoderClassifier, EncoderConfig
from accelerate_tpu.utils.random import set_seed

# reuse the MRPC-shaped synthetic data + loader wiring from the base example
import os
import sys

sys.path.append(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from nlp_example import get_dataloaders  # noqa: E402


def training_function(config, args):
    # New for this feature: the accumulation count lives on the Accelerator
    accelerator = Accelerator(
        mixed_precision=args.mixed_precision,
        gradient_accumulation_steps=int(args.gradient_accumulation_steps),
    )
    lr = config["lr"]
    num_epochs = int(config["num_epochs"])
    seed = int(config["seed"])
    batch_size = int(config["batch_size"])

    set_seed(seed)
    model_config = EncoderConfig.tiny() if (args.cpu or args.tiny) else EncoderConfig.bert_base()
    train_dataloader, eval_dataloader = get_dataloaders(
        accelerator, batch_size, model_config,
        train_len=config.get("train_len", 512), eval_len=config.get("eval_len", 128),
    )

    model_def = EncoderClassifier(model_config, mesh=accelerator.mesh)
    variables = model_def.init_variables(
        jax.random.PRNGKey(seed), batch_size=batch_size, seq_len=min(model_config.max_seq_len, 128)
    )
    total_steps = len(train_dataloader) * num_epochs // accelerator.gradient_accumulation_steps
    warmup = min(100, max(total_steps // 10, 1))
    lr_schedule = optax.warmup_cosine_decay_schedule(0.0, lr, warmup, max(total_steps, warmup + 1))

    model, optimizer, train_dataloader, eval_dataloader, lr_scheduler = accelerator.prepare(
        Model(model_def, variables), optax.adamw(lr_schedule), train_dataloader, eval_dataloader, lr_schedule
    )

    for epoch in range(num_epochs):
        model.train()
        for batch in train_dataloader:
            # the accumulate() context does the step gating: grads fold into
            # the on-device fp32 buffer every micro-step; optimizer.step()
            # becomes a real update only when the effective batch is complete
            with accelerator.accumulate(model):
                outputs = model(
                    batch["input_ids"],
                    attention_mask=batch["attention_mask"],
                    token_type_ids=batch["token_type_ids"],
                    labels=batch["labels"],
                    deterministic=False,
                )
                accelerator.backward(outputs["loss"])
                optimizer.step()
                lr_scheduler.step()
                optimizer.zero_grad()

        model.eval()
        correct = total = 0
        for batch in eval_dataloader:
            outputs = model(
                batch["input_ids"],
                attention_mask=batch["attention_mask"],
                token_type_ids=batch["token_type_ids"],
            )
            predictions = outputs["logits"].argmax(axis=-1)
            predictions, references = accelerator.gather_for_metrics((predictions, batch["labels"]))
            correct += int((np.asarray(predictions) == np.asarray(references)).sum())
            total += int(np.asarray(references).shape[0])
        accelerator.print(f"epoch {epoch}: {{'accuracy': {correct / max(total, 1):.4f}}}")

    accelerator.end_training()


def main():
    parser = argparse.ArgumentParser(description="Gradient-accumulation feature example.")
    parser.add_argument(
        "--mixed_precision", type=str, default=None, choices=["no", "fp16", "bf16"],
        help="Whether to use mixed precision (bf16 is the TPU-native choice).",
    )
    parser.add_argument("--cpu", action="store_true", help="Run the tiny config on CPU.")
    parser.add_argument("--tiny", action="store_true", help="Tiny model/dataset (CI).")
    parser.add_argument("--num_epochs", type=int, default=None)
    parser.add_argument("--gradient_accumulation_steps", type=int, default=2)
    args = parser.parse_args()
    config = {"lr": 2e-5, "num_epochs": args.num_epochs or 3, "seed": 42, "batch_size": 16}
    if args.tiny or args.cpu:
        config.update({"train_len": 128, "eval_len": 64})
    training_function(config, args)


if __name__ == "__main__":
    main()
