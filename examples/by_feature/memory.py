"""By-feature example: OOM-adaptive batch size.

Mirrors the reference feature example (/root/reference/examples/by_feature/
memory.py): wrap the inner training function with
`find_executable_batch_size` — on an out-of-memory failure the decorator
halves the batch size and re-enters, so one script serves every chip size.
"""

from __future__ import annotations

import argparse

import jax
import numpy as np
import optax

from accelerate_tpu import Accelerator, Model, find_executable_batch_size
from accelerate_tpu.models import EncoderClassifier, EncoderConfig
from accelerate_tpu.utils.random import set_seed

import os
import sys

sys.path.append(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from nlp_example import get_dataloaders  # noqa: E402


def training_function(config, args):
    accelerator = Accelerator(mixed_precision=args.mixed_precision)
    lr, num_epochs, seed = config["lr"], int(config["num_epochs"]), int(config["seed"])
    set_seed(seed)
    model_config = EncoderConfig.tiny() if (args.cpu or args.tiny) else EncoderConfig.bert_base()

    # New for this feature: the decorated inner function receives the batch
    # size and is retried at half size whenever it OOMs
    @find_executable_batch_size(starting_batch_size=int(config["batch_size"]))
    def inner_training_loop(batch_size):
        accelerator.print(f"Trying batch_size={batch_size}")
        accelerator.free_memory()  # drop prior attempt's engines/buffers
        train_dataloader, eval_dataloader = get_dataloaders(
            accelerator, batch_size, model_config,
            train_len=config.get("train_len", 128), eval_len=config.get("eval_len", 64),
        )
        model_def = EncoderClassifier(model_config, mesh=accelerator.mesh)
        variables = model_def.init_variables(
            jax.random.PRNGKey(seed), batch_size=batch_size,
            seq_len=min(model_config.max_seq_len, 128),
        )
        model, optimizer, train_dl, eval_dl = accelerator.prepare(
            Model(model_def, variables), optax.adamw(lr), train_dataloader, eval_dataloader
        )
        for epoch in range(num_epochs):
            model.train()
            for batch in train_dl:
                outputs = model(
                    batch["input_ids"], attention_mask=batch["attention_mask"],
                    token_type_ids=batch["token_type_ids"], labels=batch["labels"],
                    deterministic=False,
                )
                accelerator.backward(outputs["loss"])
                optimizer.step()
                optimizer.zero_grad()
            model.eval()
            correct = total = 0
            for batch in eval_dl:
                outputs = model(
                    batch["input_ids"], attention_mask=batch["attention_mask"],
                    token_type_ids=batch["token_type_ids"],
                )
                predictions = outputs["logits"].argmax(axis=-1)
                predictions, references = accelerator.gather_for_metrics(
                    (predictions, batch["labels"])
                )
                correct += int((np.asarray(predictions) == np.asarray(references)).sum())
                total += int(np.asarray(references).shape[0])
            accelerator.print(f"epoch {epoch}: {{'accuracy': {correct / max(total, 1):.4f}}}")

    inner_training_loop()
    accelerator.end_training()


def main():
    parser = argparse.ArgumentParser(description="OOM-adaptive batch size feature example.")
    parser.add_argument("--mixed_precision", type=str, default=None, choices=["no", "fp16", "bf16"])
    parser.add_argument("--cpu", action="store_true", help="Run the tiny config on CPU.")
    parser.add_argument("--tiny", action="store_true", help="Tiny model/dataset (CI).")
    parser.add_argument("--num_epochs", type=int, default=None)
    args = parser.parse_args()
    config = {"lr": 2e-5, "num_epochs": args.num_epochs or 1, "seed": 42, "batch_size": 16}
    training_function(config, args)


if __name__ == "__main__":
    main()
