"""By-feature example: LocalSGD.

Mirrors the reference feature example (/root/reference/examples/by_feature/
local_sgd.py) — which *raises* on TPU; here LocalSGD is TPU-native: each
data-parallel replica group keeps its own parameter copy and updates it from
its own batch shard with no per-step cross-replica traffic, and parameters
average every `local_sgd_steps` (one collective per window — the multi-slice
DCN saver).
"""

from __future__ import annotations

import argparse

import jax
import numpy as np
import optax

from accelerate_tpu import Accelerator, LocalSGD, Model
from accelerate_tpu.models import EncoderClassifier, EncoderConfig
from accelerate_tpu.utils.random import set_seed

import os
import sys

sys.path.append(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from nlp_example import get_dataloaders  # noqa: E402


def training_function(config, args):
    accelerator = Accelerator(mixed_precision=args.mixed_precision)
    lr, num_epochs, seed, batch_size = (
        config["lr"], int(config["num_epochs"]), int(config["seed"]), int(config["batch_size"])
    )
    set_seed(seed)
    model_config = EncoderConfig.tiny() if (args.cpu or args.tiny) else EncoderConfig.bert_base()
    train_dataloader, eval_dataloader = get_dataloaders(
        accelerator, batch_size, model_config,
        train_len=config.get("train_len", 128), eval_len=config.get("eval_len", 64),
    )
    model_def = EncoderClassifier(model_config, mesh=accelerator.mesh)
    variables = model_def.init_variables(
        jax.random.PRNGKey(seed), batch_size=batch_size, seq_len=min(model_config.max_seq_len, 128)
    )
    model, optimizer, train_dataloader, eval_dataloader = accelerator.prepare(
        Model(model_def, variables), optax.adamw(lr), train_dataloader, eval_dataloader
    )

    for epoch in range(num_epochs):
        model.train()
        # New for this feature: the LocalSGD context + its fused local step
        with LocalSGD(accelerator, model, local_sgd_steps=args.local_sgd_steps) as loc:
            if loc.enabled:
                local_step = loc.build_local_step()
                for batch in train_dataloader:
                    local_step(batch)      # per-replica update, no sync
                    loc.step()             # every Nth call: parameter average
            else:  # trivial data axis: plain synchronous loop
                for batch in train_dataloader:
                    outputs = model(
                        batch["input_ids"], attention_mask=batch["attention_mask"],
                        token_type_ids=batch["token_type_ids"], labels=batch["labels"],
                        deterministic=False,
                    )
                    accelerator.backward(outputs["loss"])
                    optimizer.step()
                    optimizer.zero_grad()
                    loc.step()

        model.eval()
        correct = total = 0
        for batch in eval_dataloader:
            outputs = model(
                batch["input_ids"], attention_mask=batch["attention_mask"],
                token_type_ids=batch["token_type_ids"],
            )
            predictions = outputs["logits"].argmax(axis=-1)
            predictions, references = accelerator.gather_for_metrics((predictions, batch["labels"]))
            correct += int((np.asarray(predictions) == np.asarray(references)).sum())
            total += int(np.asarray(references).shape[0])
        accelerator.print(f"epoch {epoch}: {{'accuracy': {correct / max(total, 1):.4f}}}")

    accelerator.end_training()


def main():
    parser = argparse.ArgumentParser(description="LocalSGD feature example.")
    parser.add_argument("--mixed_precision", type=str, default=None, choices=["no", "fp16", "bf16"])
    parser.add_argument("--cpu", action="store_true", help="Run the tiny config on CPU.")
    parser.add_argument("--tiny", action="store_true", help="Tiny model/dataset (CI).")
    parser.add_argument("--num_epochs", type=int, default=None)
    parser.add_argument("--local_sgd_steps", type=int, default=8)
    args = parser.parse_args()
    config = {"lr": 2e-5, "num_epochs": args.num_epochs or 3, "seed": 42, "batch_size": 16}
    if args.tiny or args.cpu:
        config.update({"train_len": 128, "eval_len": 64})
    training_function(config, args)


if __name__ == "__main__":
    main()
