"""By-feature example: profiling a training loop.

Mirrors the reference feature example
(/root/reference/examples/by_feature/profiler.py): wrap the interesting
steps in `accelerator.profile(...)` and get a trace you can open in
Perfetto / TensorBoard. On TPU this drives `jax.profiler` — the trace shows
XLA ops, fusion boundaries, and HBM transfers per step; `ProfileKwargs`
carries the output directory and rank gating exactly like the reference's
handler wraps torch.profiler.

Diff this file against examples/nlp_example.py: the `# New Code #` fences
contain the entire feature.
"""

from __future__ import annotations

import argparse

import jax
import numpy as np
import optax

from accelerate_tpu import Accelerator, DataLoader, Model
from accelerate_tpu.models import EncoderClassifier, EncoderConfig
from accelerate_tpu.utils.random import set_seed

# New Code #
from accelerate_tpu.utils.dataclasses import ProfileKwargs
# End New Code #

# reuse the MRPC-shaped synthetic data + loader wiring from the base example
import os
import sys

sys.path.append(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from nlp_example import get_dataloaders  # noqa: E402

MAX_CHIP_BATCH_SIZE = 16


def training_function(config, args):
    # New Code #
    # the handler travels with the Accelerator; accelerator.profile() uses
    # it for every capture (output dir, which ranks trace)
    profile_handler = ProfileKwargs(output_trace_dir=args.trace_dir)
    accelerator = Accelerator(
        mixed_precision=args.mixed_precision, kwargs_handlers=[profile_handler]
    )
    # End New Code #
    lr = config["lr"]
    num_epochs = int(config["num_epochs"])
    seed = int(config["seed"])
    batch_size = int(config["batch_size"])

    # If the requested batch exceeds one chip's comfort zone, fall back to
    # gradient accumulation (reference nlp_example.py:124-128)
    gradient_accumulation_steps = 1
    if batch_size > MAX_CHIP_BATCH_SIZE:
        gradient_accumulation_steps = batch_size // MAX_CHIP_BATCH_SIZE
        batch_size = MAX_CHIP_BATCH_SIZE

    set_seed(seed)
    model_config = EncoderConfig.tiny() if args.cpu or args.tiny else EncoderConfig.bert_base()
    train_dataloader, eval_dataloader = get_dataloaders(
        accelerator, batch_size, model_config,
        train_len=config.get("train_len", 512), eval_len=config.get("eval_len", 128),
    )

    model_def = EncoderClassifier(model_config, mesh=accelerator.mesh)
    variables = model_def.init_variables(
        jax.random.PRNGKey(seed), batch_size=batch_size, seq_len=min(model_config.max_seq_len, 128)
    )
    total_steps = (len(train_dataloader) * num_epochs) // gradient_accumulation_steps
    warmup = min(100, max(total_steps // 10, 1))
    lr_schedule = optax.warmup_cosine_decay_schedule(0.0, lr, warmup, max(total_steps, warmup + 1))

    model, optimizer, train_dataloader, eval_dataloader, lr_scheduler = accelerator.prepare(
        Model(model_def, variables), optax.adamw(lr_schedule), train_dataloader, eval_dataloader, lr_schedule
    )

    for epoch in range(num_epochs):
        model.train()
        # New Code #
        # profile one epoch's steps; warm up OUTSIDE the trace so the
        # capture shows steady-state steps, not the XLA compile
        with accelerator.profile() as prof:
            # End New Code #
            for step, batch in enumerate(train_dataloader):
                outputs = model(
                    batch["input_ids"],
                    attention_mask=batch["attention_mask"],
                    token_type_ids=batch["token_type_ids"],
                    labels=batch["labels"],
                    deterministic=False,
                )
                loss = outputs["loss"]
                accelerator.backward(loss)
                if step % gradient_accumulation_steps == 0:
                    optimizer.step()
                    lr_scheduler.step()
                    optimizer.zero_grad()
        # New Code #
        if prof is not None:
            accelerator.print(f"epoch {epoch}: trace written under {args.trace_dir}")
        break  # one profiled epoch is the lesson; drop this to train fully
        # End New Code #

    accelerator.end_training()


def main():
    parser = argparse.ArgumentParser(description="Profiler example.")
    parser.add_argument(
        "--mixed_precision",
        type=str,
        default=None,
        choices=["no", "fp16", "bf16"],
        help="Whether to use mixed precision (bf16 is the TPU-native choice).",
    )
    parser.add_argument("--cpu", action="store_true", help="Run the tiny config on CPU.")
    parser.add_argument("--tiny", action="store_true", help="Tiny model/dataset (CI).")
    parser.add_argument("--num_epochs", type=int, default=None)
    # New Code #
    parser.add_argument("--trace_dir", type=str, default="./profile_traces",
                        help="Where jax.profiler writes the trace.")
    # End New Code #
    args = parser.parse_args()
    config = {"lr": 2e-5, "num_epochs": args.num_epochs or 1, "seed": 42, "batch_size": 16}
    if args.tiny or args.cpu:
        config.update({"train_len": 128, "eval_len": 64})
    training_function(config, args)


if __name__ == "__main__":
    main()
