"""By-feature example: checkpointing with automatic naming and resume.

Mirrors the reference feature example (/root/reference/examples/by_feature/
checkpointing.py): ProjectConfiguration(automatic_checkpoint_naming=True,
total_limit=N) rotates `checkpoints/checkpoint_<i>` dirs under project_dir,
and --resume_from_checkpoint restores everything (model, optimizer, RNG,
step counters).
"""

from __future__ import annotations

import argparse
import os

import jax
import numpy as np
import optax

from accelerate_tpu import Accelerator, Model, ProjectConfiguration
from accelerate_tpu.models import EncoderClassifier, EncoderConfig
from accelerate_tpu.utils.random import set_seed

import sys

sys.path.append(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from nlp_example import get_dataloaders  # noqa: E402


def training_function(config, args):
    # New for this feature: automatic checkpoint rotation under project_dir
    accelerator = Accelerator(
        mixed_precision=args.mixed_precision,
        project_config=ProjectConfiguration(
            project_dir=args.project_dir,
            automatic_checkpoint_naming=True,
            total_limit=2,  # keep only the 2 newest checkpoint_<i> dirs
        ),
    )
    lr, num_epochs, seed, batch_size = (
        config["lr"], int(config["num_epochs"]), int(config["seed"]), int(config["batch_size"])
    )
    set_seed(seed)
    model_config = EncoderConfig.tiny() if (args.cpu or args.tiny) else EncoderConfig.bert_base()
    train_dataloader, eval_dataloader = get_dataloaders(
        accelerator, batch_size, model_config,
        train_len=config.get("train_len", 128), eval_len=config.get("eval_len", 64),
    )
    model_def = EncoderClassifier(model_config, mesh=accelerator.mesh)
    variables = model_def.init_variables(
        jax.random.PRNGKey(seed), batch_size=batch_size, seq_len=min(model_config.max_seq_len, 128)
    )
    model, optimizer, train_dataloader, eval_dataloader = accelerator.prepare(
        Model(model_def, variables), optax.adamw(lr), train_dataloader, eval_dataloader
    )

    if args.resume_from_checkpoint:
        accelerator.print(f"Resuming from {args.resume_from_checkpoint}")
        accelerator.load_state(args.resume_from_checkpoint)

    for epoch in range(num_epochs):
        model.train()
        for batch in train_dataloader:
            outputs = model(
                batch["input_ids"], attention_mask=batch["attention_mask"],
                token_type_ids=batch["token_type_ids"], labels=batch["labels"],
                deterministic=False,
            )
            accelerator.backward(outputs["loss"])
            optimizer.step()
            optimizer.zero_grad()
        # automatic naming: writes <project_dir>/checkpoints/checkpoint_<i>
        # and evicts the oldest past total_limit
        accelerator.save_state()
        model.eval()
        correct = total = 0
        for batch in eval_dataloader:
            outputs = model(
                batch["input_ids"], attention_mask=batch["attention_mask"],
                token_type_ids=batch["token_type_ids"],
            )
            predictions = outputs["logits"].argmax(axis=-1)
            predictions, references = accelerator.gather_for_metrics((predictions, batch["labels"]))
            correct += int((np.asarray(predictions) == np.asarray(references)).sum())
            total += int(np.asarray(references).shape[0])
        accelerator.print(f"epoch {epoch}: {{'accuracy': {correct / max(total, 1):.4f}}}")

    accelerator.end_training()


def main():
    parser = argparse.ArgumentParser(description="Checkpointing feature example.")
    parser.add_argument("--mixed_precision", type=str, default=None, choices=["no", "fp16", "bf16"])
    parser.add_argument("--cpu", action="store_true", help="Run the tiny config on CPU.")
    parser.add_argument("--tiny", action="store_true", help="Tiny model/dataset (CI).")
    parser.add_argument("--num_epochs", type=int, default=None)
    parser.add_argument("--project_dir", type=str, default="checkpoint_example")
    parser.add_argument("--resume_from_checkpoint", type=str, default=None)
    args = parser.parse_args()
    config = {"lr": 2e-5, "num_epochs": args.num_epochs or 3, "seed": 42, "batch_size": 16}
    training_function(config, args)


if __name__ == "__main__":
    main()
