"""By-feature example: early stopping.

Mirrors the reference feature example
(/root/reference/examples/by_feature/early_stopping.py): track the eval
metric each epoch and stop when it hasn't improved for `--patience` epochs.

The distributed subtlety (and the reason this is an Accelerate feature, not
three lines of user code): the stop decision must be IDENTICAL on every
process or the job deadlocks in a collective. `accelerator.set_trigger()` /
`check_trigger()` reduce the flag across ranks so all processes break on
the same epoch — any rank observing the plateau stops everyone.

Diff this file against examples/nlp_example.py: the `# New Code #` fences
contain the entire feature.
"""

from __future__ import annotations

import argparse

import jax
import numpy as np
import optax

from accelerate_tpu import Accelerator, DataLoader, Model
from accelerate_tpu.models import EncoderClassifier, EncoderConfig
from accelerate_tpu.utils.random import set_seed

# reuse the MRPC-shaped synthetic data + loader wiring from the base example
import os
import sys

sys.path.append(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from nlp_example import get_dataloaders  # noqa: E402

MAX_CHIP_BATCH_SIZE = 16


# New Code #
class EarlyStopper:
    """Stops training when the tracked metric plateaus for `patience` epochs."""

    def __init__(self, patience: int = 2, min_delta: float = 0.0):
        self.patience = patience
        self.min_delta = min_delta
        self.best = -float("inf")
        self.bad_epochs = 0

    def should_stop(self, metric: float) -> bool:
        if metric > self.best + self.min_delta:
            self.best = metric
            self.bad_epochs = 0
        else:
            self.bad_epochs += 1
        return self.bad_epochs >= self.patience
# End New Code #


def training_function(config, args):
    accelerator = Accelerator(mixed_precision=args.mixed_precision)
    lr = config["lr"]
    num_epochs = int(config["num_epochs"])
    seed = int(config["seed"])
    batch_size = int(config["batch_size"])

    # If the requested batch exceeds one chip's comfort zone, fall back to
    # gradient accumulation (reference nlp_example.py:124-128)
    gradient_accumulation_steps = 1
    if batch_size > MAX_CHIP_BATCH_SIZE:
        gradient_accumulation_steps = batch_size // MAX_CHIP_BATCH_SIZE
        batch_size = MAX_CHIP_BATCH_SIZE

    set_seed(seed)
    model_config = EncoderConfig.tiny() if args.cpu or args.tiny else EncoderConfig.bert_base()
    train_dataloader, eval_dataloader = get_dataloaders(
        accelerator, batch_size, model_config,
        train_len=config.get("train_len", 512), eval_len=config.get("eval_len", 128),
    )

    model_def = EncoderClassifier(model_config, mesh=accelerator.mesh)
    variables = model_def.init_variables(
        jax.random.PRNGKey(seed), batch_size=batch_size, seq_len=min(model_config.max_seq_len, 128)
    )
    total_steps = (len(train_dataloader) * num_epochs) // gradient_accumulation_steps
    warmup = min(100, max(total_steps // 10, 1))
    lr_schedule = optax.warmup_cosine_decay_schedule(0.0, lr, warmup, max(total_steps, warmup + 1))

    model, optimizer, train_dataloader, eval_dataloader, lr_scheduler = accelerator.prepare(
        Model(model_def, variables), optax.adamw(lr_schedule), train_dataloader, eval_dataloader, lr_schedule
    )

    # New Code #
    stopper = EarlyStopper(patience=int(args.patience))
    # End New Code #

    for epoch in range(num_epochs):
        model.train()
        for step, batch in enumerate(train_dataloader):
            outputs = model(
                batch["input_ids"],
                attention_mask=batch["attention_mask"],
                token_type_ids=batch["token_type_ids"],
                labels=batch["labels"],
                deterministic=False,
            )
            loss = outputs["loss"]
            accelerator.backward(loss)
            if step % gradient_accumulation_steps == 0:
                optimizer.step()
                lr_scheduler.step()
                optimizer.zero_grad()

        model.eval()
        correct = total = 0
        for step, batch in enumerate(eval_dataloader):
            outputs = model(
                batch["input_ids"],
                attention_mask=batch["attention_mask"],
                token_type_ids=batch["token_type_ids"],
            )
            predictions = outputs["logits"].argmax(axis=-1)
            predictions, references = accelerator.gather_for_metrics((predictions, batch["labels"]))
            correct += int((np.asarray(predictions) == np.asarray(references)).sum())
            total += int(np.asarray(references).shape[0])
        accelerator.print(f"epoch {epoch}: {{'accuracy': {correct / max(total, 1):.4f}}}")

        # New Code #
        # every process feeds the same gathered metric to its stopper, and
        # the trigger reduction makes the break unanimous even if a rank
        # ever computed a different local decision
        if stopper.should_stop(correct / max(total, 1)):
            accelerator.set_trigger()
        if accelerator.check_trigger():
            accelerator.print(f"early stopping at epoch {epoch} "
                              f"(no improvement for {stopper.patience} epochs)")
            break
        # End New Code #

    accelerator.end_training()


def main():
    parser = argparse.ArgumentParser(description="Early-stopping example.")
    parser.add_argument(
        "--mixed_precision",
        type=str,
        default=None,
        choices=["no", "fp16", "bf16"],
        help="Whether to use mixed precision (bf16 is the TPU-native choice).",
    )
    parser.add_argument("--cpu", action="store_true", help="Run the tiny config on CPU.")
    parser.add_argument("--tiny", action="store_true", help="Tiny model/dataset (CI).")
    parser.add_argument("--num_epochs", type=int, default=None)
    # New Code #
    parser.add_argument("--patience", type=int, default=2,
                        help="Epochs without eval improvement before stopping.")
    # End New Code #
    args = parser.parse_args()
    config = {"lr": 2e-5, "num_epochs": args.num_epochs or 3, "seed": 42, "batch_size": 16}
    if args.tiny or args.cpu:
        config.update({"train_len": 128, "eval_len": 64})
    training_function(config, args)


if __name__ == "__main__":
    main()
