"""Per-architecture inference matrix: ENCODER (bert slot).

Mirrors the reference's examples/inference/pippy/bert.py: dispatch a
BERT-family classifier with an auto device map and run batched scoring.
The TPU-native mechanism is GSPMD dispatch (big_modeling) rather than
torch PP — the encoder's bidirectional attention makes layer-pipelining a
poor fit, so this slot demonstrates the dispatch path every architecture
shares; see gpt2.py / t5.py / moe.py for the other family-specific paths.

Run (CPU sim): XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python examples/inference/bert.py --cpu --tiny
"""

from __future__ import annotations

import argparse
import os
import tempfile

import jax
import jax.numpy as jnp
import numpy as np

from accelerate_tpu import Accelerator, load_checkpoint_and_dispatch
from accelerate_tpu.big_modeling import init_empty_weights
from accelerate_tpu.models import EncoderClassifier, EncoderConfig
from accelerate_tpu.utils.random import set_seed
from accelerate_tpu.utils.serialization import (
    flatten_pytree,
    save_pytree,
    unflatten_to_like,
)


def main():
    parser = argparse.ArgumentParser(description="Encoder dispatch inference example.")
    parser.add_argument("--tiny", action="store_true", help="Tiny model (CI).")
    parser.add_argument("--cpu", action="store_true")
    parser.add_argument("--batch_size", type=int, default=8)
    parser.add_argument("--seq_len", type=int, default=32)
    args = parser.parse_args()
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    accelerator = Accelerator()
    set_seed(0)
    cfg = (
        EncoderConfig.tiny(dropout_rate=0.0, max_seq_len=64)
        if (args.tiny or args.cpu)
        else EncoderConfig(dropout_rate=0.0)  # bert-base shape
    )
    model_def = EncoderClassifier(cfg, mesh=accelerator.mesh)

    # build a bf16 checkpoint on disk, then dispatch it (the realistic path:
    # a fine-tuned checkpoint served from storage)
    sample = jnp.zeros((1, args.seq_len), jnp.int32)
    abstract = init_empty_weights(model_def, sample)
    abstract = abstract["params"] if "params" in abstract else abstract
    import ml_dtypes

    rng = np.random.RandomState(0)
    flat = {
        k: (rng.standard_normal(v.shape) * 0.02).astype(ml_dtypes.bfloat16)
        for k, v in flatten_pytree(abstract).items()
    }
    with tempfile.TemporaryDirectory() as td:
        ckpt = os.path.join(td, "model.safetensors")
        save_pytree(unflatten_to_like(flat, abstract), ckpt)

        model = load_checkpoint_and_dispatch(
            model_def, ckpt, sample, device_map="auto", mesh=accelerator.mesh
        )
        ids = rng.randint(0, cfg.vocab_size, (args.batch_size, args.seq_len))
        out = model(jnp.asarray(ids))
        probs = jax.nn.softmax(out["logits"], axis=-1)
        preds = np.asarray(jax.device_get(jnp.argmax(probs, -1)))
    accelerator.print(f"encoder dispatch OK: logits {out['logits'].shape}, preds {preds.tolist()}")


if __name__ == "__main__":
    main()
