"""Inference example: tensor-parallel serving of ONE model across chips.

The reference's distributed-inference guide covers the "model fits, shard
the data" case (distributed.py here) and pipelining (pippy.py); this one is
the third deployment shape: the model's WEIGHTS shard over the mesh's
"tensor" axis (heads/mlp/vocab split; the GSPMD analog of Megatron-style
tensor parallelism), activations flow full-size, and a single generation
stream uses every chip at once — the latency-oriented layout for a model
too big (or a latency target too tight) for one chip.

Run (CPU sim): XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python examples/inference/tensor_parallel.py --tiny --cpu
"""

from __future__ import annotations

import argparse

import jax
import numpy as np

from accelerate_tpu import Accelerator
from accelerate_tpu.generation import generate
from accelerate_tpu.models import DecoderConfig, DecoderLM
from accelerate_tpu.parallel.sharding import infer_param_sharding, shard_params, unbox_params
from accelerate_tpu.utils.dataclasses import ShardingConfig
from accelerate_tpu.utils.random import set_seed


def main():
    parser = argparse.ArgumentParser(description="Tensor-parallel generation example.")
    parser.add_argument("--cpu", action="store_true")
    parser.add_argument("--tiny", action="store_true", help="Tiny model (CI).")
    parser.add_argument("--tensor_parallel", type=int, default=2)
    parser.add_argument("--max_new_tokens", type=int, default=8)
    parser.add_argument("--prompt_len", type=int, default=16)
    args = parser.parse_args()
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    sc = ShardingConfig(tensor_parallel=args.tensor_parallel, data_parallel=-1)
    accelerator = Accelerator(sharding_config=sc)
    set_seed(0)

    cfg = DecoderConfig.tiny(remat=False) if (args.cpu or args.tiny) else DecoderConfig.small_1b(remat=False)
    # the mesh-aware definition annotates activations; params get their
    # tensor-sharded layout from the same logical-axis rules
    model_def = DecoderLM(cfg, mesh=accelerator.mesh)
    variables = model_def.init_variables(
        jax.random.PRNGKey(0), batch_size=1, seq_len=args.prompt_len
    )
    params, logical_axes = unbox_params(variables["params"])
    shardings = infer_param_sharding(params, accelerator.mesh, sc, logical_axes)
    params = shard_params(params, shardings)

    n_shards = max(
        len(l.sharding.device_set) for l in jax.tree_util.tree_leaves(params)
    )
    accelerator.print(f"widest param leaf spans {n_shards} device(s)")

    ids = np.random.RandomState(7).randint(
        0, cfg.vocab_size, (1, args.prompt_len)
    ).astype(np.int32)
    out = generate(model_def, params, ids, max_new_tokens=args.max_new_tokens)
    accelerator.print(
        f"tensor-parallel generation over {accelerator.mesh.shape['tensor']}-way "
        f"TP: {np.asarray(out)[0, -args.max_new_tokens:].tolist()}"
    )


if __name__ == "__main__":
    main()
