"""Inference example: pipeline-parallel forward with `prepare_pippy`.

Mirrors the reference's examples/inference/pippy pattern
(/root/reference/examples/inference/pippy/llama.py): when one chip cannot
hold the model, split its LAYERS over the mesh's "stage" axis and stream
microbatches through the stages (GPipe). `prepare_pippy` re-lays the
scan-stacked params out per stage and returns a callable whose batch is
padded/split into microbatches automatically.

Run: accelerate-tpu launch --cpu examples/inference/pippy.py --tiny
(single process; the stage axis lives inside the process's device mesh)
"""

from __future__ import annotations

import argparse

import jax
import numpy as np

from accelerate_tpu import Accelerator, Model
from accelerate_tpu.inference import prepare_pippy
from accelerate_tpu.models import DecoderConfig, DecoderLM
from accelerate_tpu.utils.dataclasses import ShardingConfig
from accelerate_tpu.utils.random import set_seed


def main():
    parser = argparse.ArgumentParser(description="Pipeline-parallel inference example.")
    parser.add_argument("--cpu", action="store_true")
    parser.add_argument("--tiny", action="store_true", help="Tiny model (CI).")
    parser.add_argument("--num_stages", type=int, default=2)
    parser.add_argument("--batch_size", type=int, default=4)
    parser.add_argument("--seq_len", type=int, default=16)
    args = parser.parse_args()

    # a mesh with a real "stage" axis: layers shard across it
    accelerator = Accelerator(
        sharding_config=ShardingConfig(pipeline_parallel=args.num_stages)
    )
    set_seed(0)

    cfg = (
        DecoderConfig.tiny(num_layers=4)
        if (args.cpu or args.tiny)
        else DecoderConfig.small_1b()
    )
    model_def = DecoderLM(cfg, mesh=accelerator.mesh)
    variables = model_def.init_variables(
        jax.random.PRNGKey(0), batch_size=args.batch_size, seq_len=args.seq_len
    )

    pipelined = prepare_pippy(
        Model(model_def, variables),
        num_stages=args.num_stages,
        mesh=accelerator.mesh,
    )

    ids = np.random.RandomState(1).randint(
        3, cfg.vocab_size, (args.batch_size, args.seq_len)
    ).astype(np.int32)
    logits = np.asarray(jax.device_get(pipelined(ids)))
    assert logits.shape == (args.batch_size, args.seq_len, cfg.vocab_size)
    assert np.isfinite(logits).all()
    accelerator.print(
        f"pipelined forward OK: {args.num_stages} stages, logits {logits.shape}"
    )


if __name__ == "__main__":
    main()
