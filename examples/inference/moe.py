"""Per-architecture inference matrix: MIXTURE-OF-EXPERTS (the slot the
reference's pippy examples don't have — its MoE support is a DeepSpeed
passthrough; here expert parallelism is first-class).

A decoder with MoE MLP blocks serves generation with its experts sharded
over the mesh's "expert" axis: tokens route to their top-k experts via an
in-graph all-to-all over ICI.

Run (CPU sim): XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python examples/inference/moe.py --cpu --tiny
"""

from __future__ import annotations

import argparse

import jax
import numpy as np

from accelerate_tpu import Accelerator
from accelerate_tpu.generation import generate
from accelerate_tpu.models import DecoderConfig, DecoderLM
from accelerate_tpu.parallel.sharding import unbox_params
from accelerate_tpu.utils.dataclasses import ShardingConfig
from accelerate_tpu.utils.random import set_seed


def main():
    parser = argparse.ArgumentParser(description="MoE expert-parallel inference example.")
    parser.add_argument("--tiny", action="store_true")
    parser.add_argument("--cpu", action="store_true")
    parser.add_argument("--num_experts", type=int, default=4)
    parser.add_argument("--expert_parallel", type=int, default=2)
    parser.add_argument("--batch_size", type=int, default=4)
    parser.add_argument("--seq_len", type=int, default=16)
    parser.add_argument("--max_new_tokens", type=int, default=8)
    args = parser.parse_args()
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    accelerator = Accelerator(
        sharding_config=ShardingConfig(expert_parallel=args.expert_parallel)
    )
    set_seed(0)
    cfg = DecoderConfig.tiny(
        num_layers=2,
        moe_num_experts=args.num_experts,
        moe_top_k=2,
    )
    model_def = DecoderLM(cfg, mesh=accelerator.mesh)
    variables = model_def.init_variables(
        jax.random.PRNGKey(0), batch_size=args.batch_size, seq_len=args.seq_len
    )
    params, _ = unbox_params(variables["params"])

    ids = np.random.RandomState(1).randint(
        3, cfg.vocab_size, (args.batch_size, args.seq_len // 2)
    )
    out = generate(
        model_def, params, jax.numpy.asarray(ids), max_new_tokens=args.max_new_tokens
    )
    tokens = np.asarray(jax.device_get(out))
    accelerator.print(
        f"moe generation OK: experts={args.num_experts} over expert axis "
        f"{args.expert_parallel}, output {tokens.shape}"
    )


if __name__ == "__main__":
    main()
