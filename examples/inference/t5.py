"""Per-architecture inference matrix: SEQ2SEQ (t5 slot).

Mirrors the reference's examples/inference/pippy/t5.py: an encoder-decoder
dispatched with an auto device map, then cached generation — the encoder
runs once at prefill, the cross-attention K/V freeze in the cache, and the
decoder scans one compiled decode step.

Run (CPU sim): XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python examples/inference/t5.py --cpu --tiny
"""

from __future__ import annotations

import argparse
import os
import tempfile

import jax
import jax.numpy as jnp
import numpy as np

from accelerate_tpu import Accelerator, load_checkpoint_and_dispatch
from accelerate_tpu.big_modeling import init_empty_weights
from accelerate_tpu.generation import generate_seq2seq_dispatched
from accelerate_tpu.models import Seq2SeqConfig, Seq2SeqLM
from accelerate_tpu.utils.random import set_seed
from accelerate_tpu.utils.serialization import (
    flatten_pytree,
    save_pytree,
    unflatten_to_like,
)


def main():
    parser = argparse.ArgumentParser(description="Seq2seq dispatch + generation example.")
    parser.add_argument("--tiny", action="store_true")
    parser.add_argument("--cpu", action="store_true")
    parser.add_argument("--batch_size", type=int, default=4)
    parser.add_argument("--seq_len", type=int, default=16)
    parser.add_argument("--max_new_tokens", type=int, default=8)
    args = parser.parse_args()
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    accelerator = Accelerator()
    set_seed(0)
    cfg = (
        Seq2SeqConfig.tiny()
        if (args.tiny or args.cpu)
        else Seq2SeqConfig()  # t5-base shape
    )
    model_def = Seq2SeqLM(cfg, mesh=accelerator.mesh)

    enc_sample = jnp.zeros((1, args.seq_len), jnp.int32)
    dec_sample = jnp.zeros((1, 4), jnp.int32)
    abstract = init_empty_weights(model_def, enc_sample, dec_sample)
    abstract = abstract["params"] if "params" in abstract else abstract
    import ml_dtypes

    rng = np.random.RandomState(0)
    flat = {
        k: (rng.standard_normal(v.shape) * 0.02).astype(ml_dtypes.bfloat16)
        for k, v in flatten_pytree(abstract).items()
    }
    with tempfile.TemporaryDirectory() as td:
        ckpt = os.path.join(td, "model.safetensors")
        save_pytree(unflatten_to_like(flat, abstract), ckpt)

        model = load_checkpoint_and_dispatch(
            model_def, ckpt, enc_sample, dec_sample, device_map="auto"
        )
        ids = rng.randint(4, cfg.vocab_size, (args.batch_size, args.seq_len))
        out = generate_seq2seq_dispatched(
            model, jnp.asarray(ids), max_new_tokens=args.max_new_tokens
        )
        tokens = np.asarray(jax.device_get(out))
    accelerator.print(f"seq2seq dispatch + generation OK: {tokens.shape}")


if __name__ == "__main__":
    main()
