"""Inference example: distributed batch generation with
`split_between_processes`.

Mirrors the reference's examples/inference/distributed pattern
(/root/reference/examples/inference/distributed/phi2.py): a pool of prompts
is split across processes — each process generates continuations for its
share on its own chips, then the results are gathered back in order. This
is throughput-oriented offline inference (every process holds a full model
replica); see pippy.py for the model-bigger-than-one-chip case.

Run: accelerate-tpu launch --num_processes 2 --cpu examples/inference/distributed.py --tiny
"""

from __future__ import annotations

import argparse

import jax
import numpy as np

from accelerate_tpu import Accelerator
from accelerate_tpu.generation import generate
from accelerate_tpu.models import DecoderConfig, DecoderLM
from accelerate_tpu.parallel.sharding import unbox_params
from accelerate_tpu.utils.operations import gather_object
from accelerate_tpu.utils.random import set_seed


def main():
    parser = argparse.ArgumentParser(description="Distributed generation example.")
    parser.add_argument("--cpu", action="store_true")
    parser.add_argument("--tiny", action="store_true", help="Tiny model (CI).")
    parser.add_argument("--max_new_tokens", type=int, default=16)
    parser.add_argument("--num_prompts", type=int, default=8)
    parser.add_argument("--prompt_len", type=int, default=16)
    args = parser.parse_args()

    accelerator = Accelerator()
    set_seed(0)

    cfg = DecoderConfig.tiny() if (args.cpu or args.tiny) else DecoderConfig.small_1b()
    model_def = DecoderLM(cfg)
    variables = model_def.init_variables(
        jax.random.PRNGKey(0), batch_size=1, seq_len=args.prompt_len
    )
    params, _ = unbox_params(variables["params"])
    params = jax.device_put(jax.tree_util.tree_map(lambda x: x.astype(cfg.dtype), params))

    # the prompt pool: identical on every process (seeded), split by rank
    rng = np.random.RandomState(7)
    prompts = [
        rng.randint(3, cfg.vocab_size, (args.prompt_len,)).tolist()
        for _ in range(args.num_prompts)
    ]

    completions = []
    with accelerator.split_between_processes(prompts) as my_prompts:
        accelerator.print(
            f"{accelerator.num_processes} process(es), "
            f"{len(my_prompts)} prompt(s) on rank {accelerator.process_index}"
        )
        for prompt in my_prompts:
            ids = np.asarray([prompt], np.int32)
            out = generate(model_def, params, ids, max_new_tokens=args.max_new_tokens)
            completions.append(np.asarray(out)[0, len(prompt):].tolist())

    # gather preserves rank order, so completions line up with the pool
    everyone = gather_object(completions)
    assert len(everyone) == len(prompts), (len(everyone), len(prompts))
    if accelerator.is_main_process:
        for i, (prompt, completion) in enumerate(zip(prompts, everyone)):
            print(f"prompt {i}: ...{prompt[-4:]} -> {completion[:8]}...")
    accelerator.print("distributed generation done")


if __name__ == "__main__":
    main()
