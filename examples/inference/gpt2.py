"""Per-architecture inference matrix: DECODER (gpt2 slot).

Mirrors the reference's examples/inference/pippy/gpt2.py: a causal LM too
big for one chip, split over pipeline stages for a batched forward — plus
the part the reference's pippy scripts stop short of: autoregressive
generation (KV-cache decoding de-pipelines by design; generation runs on
the dispatched/materialized model).

Run (CPU sim): XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python examples/inference/gpt2.py --cpu --tiny
"""

from __future__ import annotations

import argparse

import jax
import numpy as np

from accelerate_tpu import Accelerator, Model
from accelerate_tpu.generation import generate
from accelerate_tpu.inference import prepare_pippy
from accelerate_tpu.models import DecoderConfig, DecoderLM
from accelerate_tpu.parallel.sharding import unbox_params
from accelerate_tpu.utils.dataclasses import ShardingConfig
from accelerate_tpu.utils.random import set_seed


def main():
    parser = argparse.ArgumentParser(description="Decoder pipelined inference example.")
    parser.add_argument("--tiny", action="store_true")
    parser.add_argument("--cpu", action="store_true")
    parser.add_argument("--num_stages", type=int, default=2)
    parser.add_argument("--batch_size", type=int, default=4)
    parser.add_argument("--seq_len", type=int, default=16)
    parser.add_argument("--max_new_tokens", type=int, default=8)
    args = parser.parse_args()
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    accelerator = Accelerator(
        sharding_config=ShardingConfig(pipeline_parallel=args.num_stages)
    )
    set_seed(0)
    cfg = (
        DecoderConfig.tiny(num_layers=4)
        if (args.tiny or args.cpu)
        else DecoderConfig.small_1b()
    )
    model_def = DecoderLM(cfg, mesh=accelerator.mesh)
    variables = model_def.init_variables(
        jax.random.PRNGKey(0), batch_size=args.batch_size, seq_len=args.seq_len
    )

    # 1) pipelined batched forward (scoring/perplexity workloads)
    pipelined = prepare_pippy(
        Model(model_def, variables), num_stages=args.num_stages, mesh=accelerator.mesh
    )
    ids = np.random.RandomState(1).randint(3, cfg.vocab_size, (args.batch_size, args.seq_len))
    logits = pipelined(jax.numpy.asarray(ids))
    accelerator.print(f"pipelined forward OK: logits {logits.shape}")

    # 2) generation: KV-cache decode on the plain (non-pipelined) model
    params, _ = unbox_params(variables["params"])
    gen = generate(
        model_def, params, jax.numpy.asarray(ids[:, : args.seq_len // 2]),
        max_new_tokens=args.max_new_tokens,
    )
    accelerator.print(f"generation OK: {np.asarray(jax.device_get(gen)).shape}")


if __name__ == "__main__":
    main()
