"""Inference example: distributed seq2seq generation (the t5 slot).

Mirrors the reference's examples/inference/pippy/t5.py capability on the
TPU-native stack: an encoder-decoder model serving batched generation, with
the prompt pool split across processes (`split_between_processes`) and the
results gathered back in order. Each process holds a full model replica and
runs the cached encode-once/decode-scan loop on its own chips.

Run: accelerate-tpu launch --num_processes 2 --cpu \
         examples/inference/distributed_seq2seq.py --tiny
"""

from __future__ import annotations

import argparse

import jax
import numpy as np

from accelerate_tpu import Accelerator
from accelerate_tpu.generation import generate_seq2seq
from accelerate_tpu.models import Seq2SeqConfig, Seq2SeqLM
from accelerate_tpu.parallel.sharding import unbox_params
from accelerate_tpu.utils.operations import gather_object
from accelerate_tpu.utils.random import set_seed


def main():
    parser = argparse.ArgumentParser(description="Distributed seq2seq generation example.")
    parser.add_argument("--cpu", action="store_true")
    parser.add_argument("--tiny", action="store_true", help="Tiny model (CI).")
    parser.add_argument("--max_new_tokens", type=int, default=8)
    parser.add_argument("--num_prompts", type=int, default=8)
    parser.add_argument("--prompt_len", type=int, default=16)
    args = parser.parse_args()
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    accelerator = Accelerator()
    set_seed(0)

    cfg = (
        Seq2SeqConfig.tiny(max_cache_len=32)
        if (args.cpu or args.tiny)
        else Seq2SeqConfig(vocab_size=32_128, num_layers=12, embed_dim=768)
    )
    model_def = Seq2SeqLM(cfg)
    variables = model_def.init_variables(
        jax.random.PRNGKey(0), batch_size=1, seq_len=args.prompt_len
    )
    params, _ = unbox_params(variables["params"])
    params = jax.device_put(params)

    # identical seeded prompt pool on every process, split by rank
    rng = np.random.RandomState(7)
    prompts = rng.randint(3, cfg.vocab_size, (args.num_prompts, args.prompt_len))
    with accelerator.split_between_processes(list(range(args.num_prompts))) as my_ids:
        my_prompts = prompts[np.asarray(my_ids, int)]
        out = generate_seq2seq(
            model_def, params, my_prompts.astype(np.int32),
            max_new_tokens=args.max_new_tokens,
        )
        local = [(int(i), np.asarray(out[j]).tolist()) for j, i in enumerate(my_ids)]

    everyone = gather_object([local])
    merged = dict(pair for rank_items in everyone for pair in rank_items)
    assert sorted(merged) == list(range(args.num_prompts)), sorted(merged)
    accelerator.print(
        f"generated {args.max_new_tokens} target tokens for "
        f"{args.num_prompts} source sequences across "
        f"{accelerator.num_processes} process(es); first: {merged[0][:8]}"
    )


if __name__ == "__main__":
    main()
