"""Canonical CV example: ResNet image classification under data parallelism.

Mirrors the user-API shape of the reference CV example
(/root/reference/examples/cv_example.py:90-180: custom Dataset -> Accelerator
-> prepare -> imperative loop -> eval accuracy). ResNet-50 on TPU; the tiny
preset on CPU (--cpu). Data is synthetic prototype-per-class imagery (no
network egress in this image) — the point is the training contract: BatchNorm
running statistics thread through the jit as mutable state, eval uses the
running averages, and accuracy is computed with gather_for_metrics across
processes.
"""

from __future__ import annotations

import argparse

import jax
import numpy as np
import optax

from accelerate_tpu import Accelerator, DataLoader, Model
from accelerate_tpu.models import ResNet, VisionConfig
from accelerate_tpu.utils.random import set_seed


class PrototypeImageDataset:
    """K class prototypes + gaussian noise: learnable in a few steps, shaped
    like the reference's pets dataset (image tensor + integer label)."""

    def __init__(self, length: int, image_size: int, num_classes: int, seed: int):
        rng = np.random.default_rng(seed)
        self.protos = rng.normal(size=(num_classes, image_size, image_size, 3)).astype(np.float32)
        self.labels = rng.integers(0, num_classes, size=length).astype(np.int32)
        self.noise_seed = seed
        self.length = length

    def __len__(self):
        return self.length

    def __getitem__(self, i):
        rng = np.random.default_rng(self.noise_seed * 100_003 + i)
        img = self.protos[self.labels[i]] + 0.25 * rng.normal(size=self.protos.shape[1:]).astype(np.float32)
        return {"image": img.astype(np.float32), "label": self.labels[i]}


def training_function(config, args):
    accelerator = Accelerator(mixed_precision=args.mixed_precision)
    lr = config["lr"]
    num_epochs = int(config["num_epochs"])
    seed = int(config["seed"])
    batch_size = int(config["batch_size"])
    image_size = int(config["image_size"])

    set_seed(seed)
    model_config = (
        VisionConfig.tiny(image_size=image_size)
        if (args.cpu or args.tiny)
        else VisionConfig.resnet50(num_classes=config["num_classes"], image_size=image_size)
    )

    train_ds = PrototypeImageDataset(config["train_len"], image_size, config["num_classes"], seed=seed)
    eval_ds = PrototypeImageDataset(config["eval_len"], image_size, config["num_classes"], seed=seed + 1)
    train_dataloader = DataLoader(train_ds, batch_size=batch_size, shuffle=True, drop_last=True)
    eval_dataloader = DataLoader(eval_ds, batch_size=batch_size, shuffle=False)

    model_def = ResNet(model_config)
    variables = model_def.init_variables(jax.random.PRNGKey(seed), batch_size=batch_size, image_size=image_size)
    model, optimizer, train_dataloader, eval_dataloader = accelerator.prepare(
        Model(model_def, variables), optax.sgd(lr, momentum=0.9), train_dataloader, eval_dataloader
    )

    for epoch in range(num_epochs):
        model.train()
        for batch in train_dataloader:
            outputs = model(batch["image"], labels=batch["label"], train=True)
            accelerator.backward(outputs["loss"])
            optimizer.step()
            optimizer.zero_grad()

        model.eval()
        correct = total = 0
        for batch in eval_dataloader:
            outputs = model(batch["image"])
            predictions = outputs["logits"].argmax(axis=-1)
            predictions, references = accelerator.gather_for_metrics((predictions, batch["label"]))
            correct += int((np.asarray(predictions) == np.asarray(references)).sum())
            total += int(np.asarray(references).shape[0])
        accelerator.print(f"epoch {epoch}: accuracy = {100 * correct / max(total, 1):.2f}%")

    accelerator.end_training()


def main():
    parser = argparse.ArgumentParser(description="Simple example of a CV training script.")
    parser.add_argument(
        "--mixed_precision", type=str, default=None, choices=["no", "fp16", "bf16"],
        help="Whether to use mixed precision (bf16 is the TPU-native choice).",
    )
    parser.add_argument("--cpu", action="store_true", help="Run the tiny config on CPU.")
    parser.add_argument("--tiny", action="store_true", help="Tiny model/dataset (CI).")
    parser.add_argument("--num_epochs", type=int, default=None)
    args = parser.parse_args()
    config = {"lr": 0.02, "num_epochs": args.num_epochs or 3, "seed": 42, "batch_size": 16,
              "image_size": 224, "num_classes": 37, "train_len": 512, "eval_len": 128}
    if args.tiny or args.cpu:
        config.update({"image_size": 32, "num_classes": 8, "train_len": 128, "eval_len": 64, "batch_size": 8})
    training_function(config, args)


if __name__ == "__main__":
    main()
