#!/bin/bash
# Cloud TPU pod submit recipe: the launcher does the per-worker ssh fan-out
# itself (reference tpu_pod_launcher analog), so this is a plain shell
# script you run from anywhere with gcloud credentials.
set -euo pipefail

TPU_NAME=${TPU_NAME:-my-v5e-pod}
TPU_ZONE=${TPU_ZONE:-us-west4-a}
TPU_PROJECT=${TPU_PROJECT:-my-project}

exec accelerate-tpu launch \
  --tpu_name "$TPU_NAME" \
  --tpu_zone "$TPU_ZONE" \
  --tpu_project "$TPU_PROJECT" \
  --mixed_precision bf16 \
  --fsdp -1 \
  train.py "$@"
