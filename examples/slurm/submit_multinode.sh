#!/bin/bash
# Multi-node submit recipe (reference examples/slurm/submit_multinode.sh
# analog). One task per node; accelerate-tpu launch inside each task reads
# the rendezvous info from the environment this script derives from slurm.
#SBATCH --job-name=accelerate-tpu-train
#SBATCH --nodes=2
#SBATCH --ntasks-per-node=1
#SBATCH --cpus-per-task=32
#SBATCH --time=08:00:00
#SBATCH --output=%x_%j.out

set -euo pipefail

export MAIN_IP=$(scontrol show hostnames "$SLURM_JOB_NODELIST" | head -n1)
export MAIN_PORT=29500

srun bash -c '
  accelerate-tpu launch \
    --num_processes "$SLURM_NNODES" \
    --main_process_ip "$MAIN_IP" \
    --main_process_port "$MAIN_PORT" \
    --mixed_precision bf16 \
    --fsdp -1 \
    train.py --epochs 3
'
